// wirecore — native frame engine for the TCP driver's hot data path.
//
// The reference's transport is compiled Go (network.go); this is the
// rebuild's native runtime core: framed send/receive over blocking
// sockets, called from Python via ctypes (which drops the GIL for the
// duration of each call, so rank threads stream frames concurrently).
//
// Wire frame (matches mpi_tpu/backends/tcp.py):
//     kind:u8  tag:i64le  length:u32le  payload[length]
//
// Send uses writev so the 13-byte header and an arbitrarily large payload
// go to the kernel in one syscall without concatenating them in user
// space (the Python fallback builds a header+payload bytes object — an
// extra full-payload copy per frame).
//
// Signal cooperation: EINTR is returned to the caller (with progress
// recorded in *progress) instead of being retried in C — returning to
// the interpreter lets CPython run pending signal handlers (Ctrl+C)
// exactly like the pure-Python path, after which the caller resumes the
// same call with the same progress pointer.
//
// All functions return 0 on success or -errno on failure; kPeerClosed
// means the peer closed cleanly (recv side). They never throw and never
// touch Python state. Little-endian hosts only — the loader enforces
// sys.byteorder == "little" (the memcpy'd tag/length below are raw host
// order).
//
// Stage scratch (v4): every entry point takes a nullable uint64_t
// *stages — a caller-owned, caller-zeroed scratch array that the call
// ACCUMULATES per-stage nanoseconds and counts into, so the tracer can
// name where a frame's microseconds went without any locking (the
// scratch is private to one in-flight call; an -EINTR resume keeps
// accumulating into the same array). Layout:
//   send (wc_send_frame / wc_send_frame2):
//     stages[0] += ns assembling the header        (encode stage)
//     stages[1] += ns inside writev                (syscall stage)
//     stages[2] += writev invocations
//     stages[3] += bytes accepted by the kernel
//   recv (wc_recv_exact):
//     stages[0] += ns inside recv
//     stages[1] += recv invocations
//     stages[2] += bytes received
// Pass nullptr to skip all clock reads (the untraced hot path).

#include <cerrno>
#include <cstdint>
#include <cstring>
#include <ctime>

#include <sys/socket.h>
#include <sys/uio.h>
#include <unistd.h>

namespace {

constexpr int kPeerClosed = 1000;
constexpr uint64_t kHeaderLen = 13;

inline uint64_t now_ns() {
  timespec ts;
  ::clock_gettime(CLOCK_MONOTONIC, &ts);
  return static_cast<uint64_t>(ts.tv_sec) * 1000000000ull +
         static_cast<uint64_t>(ts.tv_nsec);
}

}  // namespace

extern "C" {

// Send one frame: header (kind, tag, length) + payload via writev.
// *progress counts total frame bytes already written (header included);
// start with 0 and re-invoke unchanged after -EINTR.
int wc_send_frame(int fd, uint8_t kind, int64_t tag, const uint8_t *payload,
                  uint32_t length, uint64_t *progress, uint64_t *stages) {
  const uint64_t t_asm = stages ? now_ns() : 0;
  uint8_t header[kHeaderLen];
  header[0] = kind;
  std::memcpy(header + 1, &tag, 8);
  std::memcpy(header + 9, &length, 4);
  if (stages) stages[0] += now_ns() - t_asm;
  const uint64_t total = kHeaderLen + length;
  while (*progress < total) {
    uint64_t done = *progress;
    iovec iov[2];
    int iovcnt = 0;
    if (done < kHeaderLen) {
      iov[iovcnt].iov_base = header + done;
      iov[iovcnt].iov_len = kHeaderLen - done;
      ++iovcnt;
      done = 0;
    } else {
      done -= kHeaderLen;
    }
    if (length > done) {
      iov[iovcnt].iov_base = const_cast<uint8_t *>(payload + done);
      iov[iovcnt].iov_len = length - done;
      ++iovcnt;
    }
    const uint64_t t_io = stages ? now_ns() : 0;
    ssize_t n = ::writev(fd, iov, iovcnt);
    if (stages) {
      stages[1] += now_ns() - t_io;
      stages[2] += 1;
    }
    if (n < 0) return -errno;  // -EINTR resumes from *progress
    if (stages) stages[3] += static_cast<uint64_t>(n);
    *progress += static_cast<uint64_t>(n);
  }
  return 0;
}

// Two-segment frame send: header + prefix + payload in ONE writev.
// The zero-copy data path for ndarray sends — the codec's type prefix
// (kind, dtype, shape) and the array's own memory leave without ever
// being concatenated; the wire sees one frame of length
// prefix_len + payload_len, indistinguishable from wc_send_frame's.
// *progress counts total frame bytes written; resume after -EINTR.
int wc_send_frame2(int fd, uint8_t kind, int64_t tag,
                   const uint8_t *prefix, uint32_t prefix_len,
                   const uint8_t *payload, uint32_t payload_len,
                   uint64_t *progress, uint64_t *stages) {
  const uint64_t t_asm = stages ? now_ns() : 0;
  const uint64_t length64 =
      static_cast<uint64_t>(prefix_len) + payload_len;
  if (length64 > 0xFFFFFFFFull) return -EMSGSIZE;
  const uint32_t length = static_cast<uint32_t>(length64);
  uint8_t header[kHeaderLen];
  header[0] = kind;
  std::memcpy(header + 1, &tag, 8);
  std::memcpy(header + 9, &length, 4);
  if (stages) stages[0] += now_ns() - t_asm;
  const uint64_t total = kHeaderLen + length64;
  while (*progress < total) {
    uint64_t done = *progress;
    iovec iov[3];
    int iovcnt = 0;
    if (done < kHeaderLen) {
      iov[iovcnt].iov_base = header + done;
      iov[iovcnt].iov_len = kHeaderLen - done;
      ++iovcnt;
      done = 0;
    } else {
      done -= kHeaderLen;
    }
    if (prefix_len > done) {
      iov[iovcnt].iov_base = const_cast<uint8_t *>(prefix + done);
      iov[iovcnt].iov_len = prefix_len - done;
      ++iovcnt;
      done = 0;
    } else {
      done -= prefix_len;
    }
    if (payload_len > done) {
      iov[iovcnt].iov_base = const_cast<uint8_t *>(payload + done);
      iov[iovcnt].iov_len = payload_len - done;
      ++iovcnt;
    }
    const uint64_t t_io = stages ? now_ns() : 0;
    ssize_t n = ::writev(fd, iov, iovcnt);
    if (stages) {
      stages[1] += now_ns() - t_io;
      stages[2] += 1;
    }
    if (n < 0) return -errno;  // -EINTR resumes from *progress
    if (stages) stages[3] += static_cast<uint64_t>(n);
    *progress += static_cast<uint64_t>(n);
  }
  return 0;
}

// Receive exactly n bytes into buf. *progress counts bytes already read;
// start with 0 and re-invoke unchanged after -EINTR.
int wc_recv_exact(int fd, uint8_t *buf, uint64_t n, uint64_t *progress,
                  uint64_t *stages) {
  while (*progress < n) {
    const uint64_t t_io = stages ? now_ns() : 0;
    ssize_t r = ::recv(fd, buf + *progress, n - *progress, 0);
    if (stages) {
      stages[0] += now_ns() - t_io;
      stages[1] += 1;
    }
    if (r < 0) return -errno;  // -EINTR resumes from *progress
    if (r == 0) return kPeerClosed;
    if (stages) stages[2] += static_cast<uint64_t>(r);
    *progress += static_cast<uint64_t>(r);
  }
  return 0;
}

// Sanity probe for the loader.
int wc_version() { return 4; }

}  // extern "C"

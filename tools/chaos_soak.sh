#!/bin/bash
# Repeated seeded chaos soak (tools/ sibling of tunnel_watch.sh).
#
# Loops the slow chaos suites — the multi-seed delay/reorder bit-exact
# soak and the low-rate corruption soak — across a sweep of seeds fed
# in via MPI_TPU_CHAOS-style specs, logging one line per iteration to
# CHAOS_SOAK_LOG.md. Every fault decision is a pure function of the
# seed (mpi_tpu/chaos.py), so any failure line is an exact repro
# recipe: rerun with the printed seed.
#
# Usage:
#   tools/chaos_soak.sh            # default 10 iterations
#   tools/chaos_soak.sh 100        # longer soak
#   SEED_BASE=500 tools/chaos_soak.sh
cd "$(dirname "$0")/.." || exit 1

ITERS="${1:-10}"
SEED_BASE="${SEED_BASE:-0}"
LOG=CHAOS_SOAK_LOG.md
# Flight-recorder dumps (docs/OBSERVABILITY.md): every chaos-killed or
# deadline-failed rank in the soak leaves its postmortem here, so a
# failing seed ships with a "what was each rank doing" snapshot. The
# nightly job archives this directory as a build artifact.
PM_DIR="${MPI_TPU_POSTMORTEM_DIR:-chaos-postmortems}"
mkdir -p "$PM_DIR"
export MPI_TPU_POSTMORTEM_DIR="$(cd "$PM_DIR" && pwd)"

echo "- $(date -u '+%Y-%m-%d %H:%M UTC'): soak start iters=$ITERS seed_base=$SEED_BASE" >> "$LOG"

fails=0
for i in $(seq 1 "$ITERS"); do
  seed=$((SEED_BASE + i))
  # Yield to a foreign bench run, as tunnel_watch.sh does: chaos delay
  # timing plus a contended core makes spurious slowness, not signal.
  while pgrep -f "python[^ ]* ([^ ]*/)?bench\.py" > /dev/null 2>&1; do
    sleep 60
  done
  if JAX_PLATFORMS=cpu MPI_TPU_CHAOS_SOAK_SEED="$seed" timeout 900 \
      python -m pytest tests/test_chaos.py -q -m slow \
      -p no:cacheprovider > /tmp/chaos_soak_run.log 2>&1; then
    echo "- $(date -u '+%Y-%m-%d %H:%M UTC'): seed $seed OK" >> "$LOG"
  else
    fails=$((fails + 1))
    tail -5 /tmp/chaos_soak_run.log | sed 's/^/    /' >> "$LOG"
    echo "- $(date -u '+%Y-%m-%d %H:%M UTC'): seed $seed FAIL (log above)" >> "$LOG"
  fi
  # Crash drive: one seeded rank-death under the real launcher per
  # iteration — the in-process slow suites never kill a rank, so this
  # is what actually exercises the flight-recorder dump + job-report
  # path and fills the archived postmortem dir. Expected exit: the
  # chaos crash code (37); anything else (including success) is a
  # soak failure.
  crash_prog=$(mktemp /tmp/chaos_soak_crash_XXXX.py)
  cat > "$crash_prog" <<'PYEOF'
import sys
import mpi_tpu
mpi_tpu.init()
r, n = mpi_tpu.rank(), mpi_tpu.size()
for step in range(200):
    mpi_tpu.sendrecv(r, dest=(r + 1) % n, source=(r - 1) % n, tag=step)
mpi_tpu.finalize()
sys.exit(0)
PYEOF
  port=$((21000 + (seed % 500) * 4))
  JAX_PLATFORMS=cpu timeout 120 python -m mpi_tpu.launch.mpirun \
      --port-base "$port" --timeout 30 --postmortem-dir "$MPI_TPU_POSTMORTEM_DIR" \
      --chaos "$seed:1:crash@6" 2 "$crash_prog" \
      > /tmp/chaos_soak_crash.log 2>&1
  crash_rc=$?
  rm -f "$crash_prog"
  if [ "$crash_rc" -eq 37 ] && \
      grep -q "last in-flight op" /tmp/chaos_soak_crash.log; then
    echo "- $(date -u '+%Y-%m-%d %H:%M UTC'): seed $seed crash-drive OK (job postmortem collected)" >> "$LOG"
  else
    fails=$((fails + 1))
    tail -5 /tmp/chaos_soak_crash.log | sed 's/^/    /' >> "$LOG"
    echo "- $(date -u '+%Y-%m-%d %H:%M UTC'): seed $seed crash-drive FAIL rc=$crash_rc" >> "$LOG"
  fi
done

dumps=$(ls "$MPI_TPU_POSTMORTEM_DIR"/postmortem-*.json 2>/dev/null | wc -l)
echo "- $(date -u '+%Y-%m-%d %H:%M UTC'): soak done, $fails/$ITERS failed, $dumps flight-recorder dump(s) in $MPI_TPU_POSTMORTEM_DIR" >> "$LOG"
exit "$((fails > 0))"

#!/bin/bash
# Repeated seeded chaos soak (tools/ sibling of tunnel_watch.sh).
#
# Loops the slow chaos suites — the multi-seed delay/reorder bit-exact
# soak and the low-rate corruption soak — across a sweep of seeds fed
# in via MPI_TPU_CHAOS-style specs, logging one line per iteration to
# CHAOS_SOAK_LOG.md. Every fault decision is a pure function of the
# seed (mpi_tpu/chaos.py), so any failure line is an exact repro
# recipe: rerun with the printed seed.
#
# Usage:
#   tools/chaos_soak.sh            # default 10 iterations
#   tools/chaos_soak.sh 100        # longer soak
#   SEED_BASE=500 tools/chaos_soak.sh
cd "$(dirname "$0")/.." || exit 1

ITERS="${1:-10}"
SEED_BASE="${SEED_BASE:-0}"
LOG=CHAOS_SOAK_LOG.md

echo "- $(date -u '+%Y-%m-%d %H:%M UTC'): soak start iters=$ITERS seed_base=$SEED_BASE" >> "$LOG"

fails=0
for i in $(seq 1 "$ITERS"); do
  seed=$((SEED_BASE + i))
  # Yield to a foreign bench run, as tunnel_watch.sh does: chaos delay
  # timing plus a contended core makes spurious slowness, not signal.
  while pgrep -f "python[^ ]* ([^ ]*/)?bench\.py" > /dev/null 2>&1; do
    sleep 60
  done
  if JAX_PLATFORMS=cpu MPI_TPU_CHAOS_SOAK_SEED="$seed" timeout 900 \
      python -m pytest tests/test_chaos.py -q -m slow \
      -p no:cacheprovider > /tmp/chaos_soak_run.log 2>&1; then
    echo "- $(date -u '+%Y-%m-%d %H:%M UTC'): seed $seed OK" >> "$LOG"
  else
    fails=$((fails + 1))
    tail -5 /tmp/chaos_soak_run.log | sed 's/^/    /' >> "$LOG"
    echo "- $(date -u '+%Y-%m-%d %H:%M UTC'): seed $seed FAIL (log above)" >> "$LOG"
  fi
done

echo "- $(date -u '+%Y-%m-%d %H:%M UTC'): soak done, $fails/$ITERS failed" >> "$LOG"
exit "$((fails > 0))"

#!/usr/bin/env python
"""bench_gate — diff two bench artifacts and fail CI on regression.

Compares a CURRENT bench artifact against a BASE artifact using the
same directional, materiality-floored check the bench itself runs
against the committed ``BENCH_FULL.json`` (``bench._regression_check``
— one classifier, no drift between local and CI verdicts).

Artifacts accepted:

* ``BENCH_FULL.json`` — the bench's own full-result dict (flat keys).
* ``--mpi-metrics-out`` per-rank artifacts — recognised by their
  ``schema_version``/``ops`` shape and flattened into comparable
  numeric keys (``op_<name>_p50_us`` etc.) before the check runs.

Exit codes: 0 ok (or ``--warn-only``), 1 regression(s) found,
2 artifact unreadable/incomparable.

Usage::

    python tools/bench_gate.py BASE.json CURRENT.json \
        [--pct 30] [--keys k1,k2,...] [--warn-only]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Any, Dict, Optional

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load(path: str) -> Optional[Dict[str, Any]]:
    try:
        with open(path) as f:
            rec = json.load(f)
    except (OSError, ValueError) as exc:
        print(f"bench_gate: cannot read {path}: {exc}", file=sys.stderr)
        return None
    return rec if isinstance(rec, dict) else None


def _flatten_metrics(rec: Dict[str, Any]) -> Dict[str, Any]:
    """A ``--mpi-metrics-out`` artifact flattened to bench-style keys;
    any other dict passes through unchanged."""
    if "schema_version" not in rec or "ops" not in rec:
        return rec
    flat: Dict[str, Any] = {
        # _regression_check's like-for-like gate needs these present
        # and equal on both sides; metrics artifacts are always
        # self-comparable.
        "platform": rec.get("platform", "metrics"),
        "smoke": False,
    }
    for op, stats in (rec.get("ops") or {}).items():
        if not isinstance(stats, dict):
            continue
        for stat, val in stats.items():
            if isinstance(val, (int, float)) and not isinstance(val, bool):
                suffix = stat if stat.endswith(("_us", "_ms")) \
                    else f"{stat}_count" if stat == "count" else stat
                flat[f"op_{op}_{suffix}"] = val
    for k, v in rec.items():
        if isinstance(v, (int, float)) and not isinstance(v, bool) \
                and k not in flat:
            flat[k] = v
    return flat


def main(argv: Optional[list] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="bench_gate",
        description="fail when CURRENT regresses vs BASE (bench's own "
                    "directional check)")
    ap.add_argument("base", help="baseline artifact (previous round)")
    ap.add_argument("current", help="artifact under test")
    ap.add_argument("--pct", type=float, default=None,
                    help="regression threshold percent "
                         "(default: MPI_TPU_BENCH_REGRESS_PCT or 30)")
    ap.add_argument("--keys", default=None,
                    help="comma-separated key allowlist: only these "
                         "keys can gate (others still reported)")
    ap.add_argument("--warn-only", action="store_true",
                    help="report regressions but exit 0 (bootstrap "
                         "rounds / noisy boxes)")
    args = ap.parse_args(argv)

    if args.pct is not None:
        os.environ["MPI_TPU_BENCH_REGRESS_PCT"] = str(args.pct)

    sys.path.insert(0, _REPO)
    import bench  # noqa: E402  — top-level bench imports are light

    base = _load(args.base)
    cur = _load(args.current)
    if base is None or cur is None:
        return 2
    base = _flatten_metrics(base)
    cur = _flatten_metrics(cur)

    # _regression_check mutates its `full` arg; gate on a copy so the
    # caller's artifact file semantics stay read-only.
    full = dict(cur)
    bench._regression_check(full, base)
    if "regressions" not in full:
        print(f"bench_gate: {full.get('regressions_vs', 'incomparable')}",
              file=sys.stderr)
        return 2

    regs = full["regressions"]
    if args.keys:
        allow = {k.strip() for k in args.keys.split(",") if k.strip()}
        gating = [r for r in regs if r["key"] in allow]
        ignored = [r for r in regs if r["key"] not in allow]
    else:
        gating, ignored = regs, []

    for r in gating:
        print(f"REGRESSION {r['key']}: {r['prev']} -> {r['now']} "
              f"({r['ratio']}x)")
    for r in ignored:
        print(f"regressed (not gated) {r['key']}: {r['prev']} -> "
              f"{r['now']} ({r['ratio']}x)")
    for r in full.get("regressions_suppressed", []):
        print(f"suppressed {r['key']}: {r['prev']} -> {r['now']} "
              f"({r['reason']})")
    if not regs:
        print("bench_gate: no regressions "
              f"({args.current} vs {args.base})")
    if gating and not args.warn_only:
        return 1
    if gating:
        print(f"bench_gate: --warn-only, not failing "
              f"({len(gating)} regression(s))")
    return 0


if __name__ == "__main__":
    sys.exit(main())

#!/bin/bash
# Probe the axon tunnel every 8 min; log to TUNNEL_LOG.md. On a
# successful probe: run the headline-only bench FIRST (MFU lands in the
# window's first minutes), persist it as BENCH_MANUAL_r05.json, then
# run the full bench and upgrade the capture — only ever overwriting
# with a line that actually carries a TPU headline (platform=tpu and a
# nonzero value), so a failed full run can never destroy a good
# headline-only capture.
cd /root/repo

is_tpu_line() {
  # Accept any genuine on-chip headline: value (MFU) when the chip
  # kind is in the peak table, else tokens/s (an unknown device_kind
  # honestly reports mfu null + value 0.0 — that capture is still
  # rare tunnel-window evidence and must never be discarded).
  echo "$1" | python -c 'import json,sys
try:
    d = json.loads(sys.stdin.read())
except ValueError:
    sys.exit(1)
ok = (d.get("platform") == "tpu"
      and not d.get("error")
      and (d.get("value") or d.get("train_tokens_per_s")))
sys.exit(0 if ok else 1)'
}

while true; do
  # Yield to any foreign bench run (the driver's end-of-round run, a
  # test-suite smoke): the probe's python process competes for the
  # box's single core and measurably skews host-side timing legs.
  # Match an actual python invocation of bench.py only — a bare
  # "bench.py" substring also matches the round driver's prompt text
  # in its own argv, which would wedge the watcher forever.
  if pgrep -f "python[^ ]* ([^ ]*/)?bench\.py" > /dev/null 2>&1; then
    sleep 120
    continue
  fi
  if timeout 75 python -c "import jax,jax.numpy as jnp; jnp.ones((128,128)).sum().block_until_ready()" > /dev/null 2>&1; then
    echo "- $(date -u '+%Y-%m-%d %H:%M UTC'): probe OK (watch loop)" >> TUNNEL_LOG.md
    if [ ! -f BENCH_MANUAL_r05.json ]; then
      echo "- $(date -u '+%Y-%m-%d %H:%M UTC'): tunnel up -> headline-only capture" >> TUNNEL_LOG.md
      MPI_TPU_BENCH_DEADLINE_S=900 timeout 1000 python bench.py --headline-only > /tmp/bench_hl.out 2> /tmp/bench_hl.err
      rc=$?
      line=$(grep -a '^{' /tmp/bench_hl.out | tail -1)
      if [ -n "$line" ] && is_tpu_line "$line"; then
        echo "$line" > BENCH_MANUAL_r05.json
        cp BENCH_FULL.json BENCH_MANUAL_r05_full.json 2>/dev/null
        cp /tmp/bench_hl.err BENCH_MANUAL_r05.stderr.log 2>/dev/null
        echo "- $(date -u '+%Y-%m-%d %H:%M UTC'): headline capture rc=$rc -> BENCH_MANUAL_r05.json" >> TUNNEL_LOG.md
      else
        echo "- $(date -u '+%Y-%m-%d %H:%M UTC'): headline capture rc=$rc yielded no TPU line" >> TUNNEL_LOG.md
      fi
    fi
    if [ -f BENCH_MANUAL_r05.json ] && [ ! -f /tmp/bench_fullrun_r05.done ]; then
      echo "- $(date -u '+%Y-%m-%d %H:%M UTC'): tunnel up -> full bench capture" >> TUNNEL_LOG.md
      MPI_TPU_BENCH_DEADLINE_S=3000 timeout 3300 python bench.py > /tmp/bench_manual.out 2> /tmp/bench_manual.err
      rc=$?
      line=$(grep -a '^{' /tmp/bench_manual.out | tail -1)
      if [ -n "$line" ] && is_tpu_line "$line"; then
        echo "$line" > BENCH_MANUAL_r05.json
        cp BENCH_FULL.json BENCH_MANUAL_r05_full.json 2>/dev/null
        cp /tmp/bench_manual.err BENCH_MANUAL_r05.stderr.log 2>/dev/null
        touch /tmp/bench_fullrun_r05.done
        echo "- $(date -u '+%Y-%m-%d %H:%M UTC'): full bench rc=$rc -> BENCH_MANUAL_r05.json (upgraded)" >> TUNNEL_LOG.md
      else
        echo "- $(date -u '+%Y-%m-%d %H:%M UTC'): full bench rc=$rc kept no TPU line" >> TUNNEL_LOG.md
      fi
    fi
  else
    echo "- $(date -u '+%Y-%m-%d %H:%M UTC'): probe TIMEOUT (watch loop)" >> TUNNEL_LOG.md
  fi
  sleep 480
done
